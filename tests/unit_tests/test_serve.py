"""Serve subsystem: spec parsing, autoscaler hysteresis, LB policies,
and a hermetic end-to-end service on the local cloud."""
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest
import requests

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.load_balancer import (LeastLoadPolicy,
                                              RoundRobinPolicy)
from skypilot_tpu.serve.service_spec import ServiceSpec


# ------------------------------------------------------------- spec

def test_service_spec_parsing():
    spec = ServiceSpec.from_yaml_config({
        'readiness_probe': {'path': '/health',
                            'initial_delay_seconds': 30},
        'replica_policy': {'min_replicas': 1, 'max_replicas': 4,
                           'target_qps_per_replica': 2.0},
        'replica_port': 9000,
    })
    assert spec.readiness_path == '/health'
    assert spec.max_replicas == 4
    round_trip = ServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert round_trip == spec


def test_service_spec_fixed_replicas():
    spec = ServiceSpec.from_yaml_config({'replicas': 2})
    assert spec.min_replicas == 2 and spec.max_replicas == 2


def test_service_spec_autoscale_requires_max():
    with pytest.raises(exceptions.InvalidTaskError):
        ServiceSpec.from_yaml_config(
            {'replica_policy': {'target_qps_per_replica': 1.0}})


# -------------------------------------------------------- autoscaler

def test_autoscaler_hysteresis():
    spec = ServiceSpec(min_replicas=1, max_replicas=10,
                       target_qps_per_replica=1.0,
                       upscale_delay_seconds=10,
                       downscale_delay_seconds=100)
    scaler = autoscalers.RequestRateAutoscaler(spec)
    t0 = 1000.0
    # 5 qps sustained -> raw target 5, but only after 10s persistence.
    for i in range(300):
        scaler.record_request(t0 + i * 0.2)
    now = t0 + 60
    assert scaler.evaluate(1, now).target_replicas == 1      # starts clock
    assert scaler.evaluate(1, now + 5).target_replicas == 1  # too soon
    assert scaler.evaluate(1, now + 11).target_replicas == 5  # fires

    # Traffic stops: downscale only after the (longer) delay.
    later = now + 200
    assert scaler.evaluate(5, later).target_replicas == 5
    assert scaler.evaluate(5, later + 50).target_replicas == 5
    assert scaler.evaluate(5, later + 101).target_replicas == 1


def test_autoscaler_respects_bounds():
    spec = ServiceSpec(min_replicas=2, max_replicas=3,
                       target_qps_per_replica=1.0,
                       upscale_delay_seconds=0,
                       downscale_delay_seconds=0)
    scaler = autoscalers.RequestRateAutoscaler(spec)
    t0 = 2000.0
    for i in range(600):
        scaler.record_request(t0 + i * 0.1)  # 10 qps -> raw 10
    scaler.evaluate(2, t0 + 60)
    assert scaler.evaluate(2, t0 + 61).target_replicas == 3  # capped
    scaler2 = autoscalers.RequestRateAutoscaler(spec)
    scaler2.evaluate(3, t0)
    assert scaler2.evaluate(3, t0 + 1).target_replicas == 2  # floor


def test_fallback_autoscaler_spot_mix():
    """Spot base + on-demand fallback (reference autoscalers.py:546):
    QPS target is served by spot; base on-demand is always on; dynamic
    fallback covers the not-yet-ready part of the spot target."""
    spec = ServiceSpec(min_replicas=1, max_replicas=10,
                       target_qps_per_replica=1.0,
                       upscale_delay_seconds=0,
                       downscale_delay_seconds=0,
                       use_spot=True,
                       base_ondemand_fallback_replicas=1,
                       dynamic_ondemand_fallback=True)
    scaler = autoscalers.make_autoscaler(spec)
    assert isinstance(scaler, autoscalers.FallbackRequestRateAutoscaler)
    t0 = 3000.0
    for i in range(180):
        scaler.record_request(t0 + i / 3.0)  # 3 qps -> target 3 spot
    scaler.evaluate(3, t0 + 60, num_ready_spot=3)
    d = scaler.evaluate(3, t0 + 61, num_ready_spot=3)
    # All spot ready: 3 spot + 1 base on-demand.
    assert (d.num_spot, d.num_ondemand) == (3, 1)
    assert d.target_replicas == 4
    # A preemption storm takes 2 spot replicas out: dynamic fallback
    # covers the gap with on-demand until spot recovers.
    d = scaler.evaluate(3, t0 + 62, num_ready_spot=1)
    assert (d.num_spot, d.num_ondemand) == (3, 1 + 2)


def test_fixed_autoscaler_spot_split():
    spec = ServiceSpec(min_replicas=2, use_spot=True,
                       base_ondemand_fallback_replicas=1)
    d = autoscalers.make_autoscaler(spec).initial()
    assert (d.target_replicas, d.num_spot, d.num_ondemand) == (3, 2, 1)


def test_spec_spot_policy_roundtrip_and_validation():
    spec = ServiceSpec.from_yaml_config({
        'replica_policy': {'min_replicas': 1, 'use_spot': True,
                           'base_ondemand_fallback_replicas': 1,
                           'dynamic_ondemand_fallback': True},
    })
    assert spec.use_spot and spec.dynamic_ondemand_fallback
    assert ServiceSpec.from_yaml_config(spec.to_yaml_config()) == spec
    with pytest.raises(exceptions.InvalidTaskError):
        ServiceSpec.from_yaml_config({
            'replica_policy': {'base_ondemand_fallback_replicas': 1},
        })  # fallback without use_spot


# -------------------------------- spot preemption rate + headroom

def test_spot_rate_estimator_ewma_decay_and_state(monkeypatch):
    """Exposure-weighted EWMA (docs/spot_serving.md): events and
    exposure decay by the SAME half-life factor, so pure time passing
    holds the rate estimate steady while fresh exposure without
    events dilutes it."""
    monkeypatch.setenv('SKYTPU_SPOT_RATE_HALFLIFE_S', '1800')
    est = autoscalers.SpotPreemptionRateEstimator()
    assert est.rate_per_replica_hour() == 0.0
    t0 = 1000.0
    est.advance(t0, 2)            # first call only anchors the clock
    assert est.rate_per_replica_hour() == 0.0
    # One half-life of 2-replica exposure, then one preemption:
    # exposure = 2 * 1800/3600 = 1.0 replica-hour.
    est.advance(t0 + 1800, 2)
    est.record_preemption()
    assert est.rate_per_replica_hour() == pytest.approx(1.0)
    # Another half-life with ZERO spot running: events and exposure
    # both halve — the estimate holds instead of decaying to zero.
    est.advance(t0 + 3600, 0)
    assert est.rate_per_replica_hour() == pytest.approx(1.0)
    # Fresh incident-free exposure dilutes the rate downward.
    est.advance(t0 + 5400, 4)
    assert est.rate_per_replica_hour() < 1.0
    # Expected losses scale with pool size and lead time.
    assert est.expected_losses(0, 300.0) == 0.0
    assert est.expected_losses(
        4, 3600.0) == pytest.approx(4 * est.rate_per_replica_hour())
    # Exact state round-trip.
    clone = autoscalers.SpotPreemptionRateEstimator()
    clone.restore(est.to_state())
    assert clone.to_state() == est.to_state()
    assert clone.rate_per_replica_hour() == est.rate_per_replica_hour()
    # Garbage / old-format state restores COLD, never raises.
    for bad in ({}, {'events': 'not-a-number', 'exposure_h': []},
                {'events': object()}, {'last_at': 'later'}):
        cold = autoscalers.SpotPreemptionRateEstimator()
        cold.restore(bad)
        assert cold.rate_per_replica_hour() == 0.0


def test_fixed_autoscaler_rate_aware_headroom(monkeypatch):
    """Rate-aware over-provisioning: a non-zero observed preemption
    rate adds ceil(rate * spot_target * lead_time) headroom to the
    spot ask, and the dynamic on-demand fallback is sized against the
    HEADROOMED plan. Zero observed rate stays bit-identical to the
    rate-blind split."""
    monkeypatch.setenv('SKYTPU_SPOT_RATE_HALFLIFE_S', '1800')
    spec = ServiceSpec(min_replicas=3, use_spot=True,
                       base_ondemand_fallback_replicas=1,
                       dynamic_ondemand_fallback=True,
                       spot_recovery_lead_time_s=1200.0)
    scaler = autoscalers.make_autoscaler(spec)
    assert isinstance(scaler, autoscalers.FixedReplicaAutoscaler)
    t0 = 5000.0
    # Cold estimator: exactly today's split (3 spot + 1 base od).
    d = scaler.evaluate(3, now=t0, num_ready_spot=3)
    assert (d.target_replicas, d.num_spot, d.num_ondemand) == (4, 3, 1)
    # 1h of 3-replica exposure with 3 preemptions -> ~1.0 per
    # replica-hour; expected losses within the 1200s lead time =
    # 1.0 * 3 * 1200/3600 = 1 replica of headroom.
    scaler.evaluate(3, now=t0 + 3600, num_ready_spot=3)
    for _ in range(3):
        scaler.record_preemption()
    d = scaler.evaluate(3, now=t0 + 3601, num_ready_spot=3)
    assert d.num_spot == 4                       # 3 target + 1 headroom
    # Dynamic fallback covers the headroomed plan: 4 wanted, 3 ready.
    assert d.num_ondemand == 1 + 1
    assert d.target_replicas == 6
    # Persistence: the rate survives a controller restart via
    # to_state()/restore() and yields the SAME decision.
    fresh = autoscalers.make_autoscaler(spec)
    fresh.restore(scaler.to_state())
    d2 = fresh.evaluate(3, now=t0 + 3601, num_ready_spot=3)
    assert (d2.num_spot, d2.num_ondemand) == (d.num_spot, d.num_ondemand)
    # Old-format state (no 'spot' key) restores cold: rate-blind
    # split, no error.
    legacy = autoscalers.make_autoscaler(spec)
    legacy.restore({})
    d3 = legacy.evaluate(3, now=t0, num_ready_spot=3)
    assert (d3.num_spot, d3.num_ondemand) == (3, 1)


def test_fallback_autoscaler_headroom_rides_qps_target(monkeypatch):
    """The QPS-derived spot target carries the same headroom: the
    estimator state also round-trips inside the request-rate
    autoscaler's persisted dict (alongside the QPS window)."""
    monkeypatch.setenv('SKYTPU_SPOT_RATE_HALFLIFE_S', '1800')
    spec = ServiceSpec(min_replicas=1, max_replicas=10,
                       target_qps_per_replica=1.0,
                       upscale_delay_seconds=0,
                       downscale_delay_seconds=0,
                       use_spot=True,
                       base_ondemand_fallback_replicas=1,
                       dynamic_ondemand_fallback=True,
                       spot_recovery_lead_time_s=1200.0)
    scaler = autoscalers.make_autoscaler(spec)
    t0 = 7000.0
    for i in range(180):
        scaler.record_request(t0 + i / 3.0)      # 3 qps -> 3 spot
    scaler.evaluate(3, t0 + 60, num_ready_spot=3)
    d = scaler.evaluate(3, t0 + 61, num_ready_spot=3)
    assert (d.num_spot, d.num_ondemand) == (3, 1)
    # An hour of 3-replica exposure with 3 preemptions -> ~1.0 per
    # replica-hour; traffic keeps flowing so the QPS target holds.
    for i in range(180):
        scaler.record_request(t0 + 3600 + i / 3.0)
    scaler.evaluate(3, t0 + 3661, num_ready_spot=3)
    for _ in range(3):
        scaler.record_preemption()
    d = scaler.evaluate(3, t0 + 3662, num_ready_spot=3)
    assert d.num_spot == 4 and d.num_ondemand == 2
    state = scaler.to_state()
    assert 'spot' in state and 'timestamps' in state
    fresh = autoscalers.make_autoscaler(spec)
    fresh.restore(state)
    assert (fresh.spot_rate.rate_per_replica_hour() ==
            pytest.approx(scaler.spot_rate.rate_per_replica_hour()))


def test_spec_spot_lead_time_roundtrip_and_validation():
    spec = ServiceSpec.from_yaml_config({
        'replica_policy': {'min_replicas': 1, 'use_spot': True,
                           'spot_recovery_lead_time_s': 600},
    })
    assert spec.spot_recovery_lead_time_s == 600.0
    assert ServiceSpec.from_yaml_config(spec.to_yaml_config()) == spec
    with pytest.raises(exceptions.InvalidTaskError):
        ServiceSpec.from_yaml_config({
            'replica_policy': {'min_replicas': 1, 'use_spot': True,
                               'spot_recovery_lead_time_s': -5},
        })


# ------------------------------------------------------------ LB

def test_round_robin_policy():
    p = RoundRobinPolicy()
    p.set_urls(['a', 'b'])
    assert [p.pick() for _ in range(4)] == ['a', 'b', 'a', 'b']


def test_least_load_policy():
    p = LeastLoadPolicy()
    p.set_urls(['a', 'b'])
    u1 = p.pick()
    u2 = p.pick()
    assert {u1, u2} == {'a', 'b'}  # spreads in-flight load
    p.done(u1)
    assert p.pick() == u1          # the drained one wins


# ------------------------------------------------------- end-to-end

@pytest.mark.slow
def test_serve_up_probe_and_proxy(isolated_state, monkeypatch):
    monkeypatch.setenv('SKYTPU_SERVE_DB',
                       str(isolated_state / 'serve.db'))
    monkeypatch.setenv('SKYTPU_SERVE_LOG_DIR',
                       str(isolated_state / 'serve_logs'))
    task = task_lib.Task(
        'svc',
        run='python -c "'
        'import http.server, os, functools; '
        'http.server.HTTPServer((\'127.0.0.1\', '
        'int(os.environ[\'SKYTPU_SERVE_PORT\'])), '
        'http.server.SimpleHTTPRequestHandler).serve_forever()"')
    task.set_resources(resources_lib.Resources(cloud='local'))
    task.service = ServiceSpec(min_replicas=1, replica_port=18080,
                               initial_delay_seconds=60,
                               readiness_timeout_seconds=3)
    result = serve_core.up(task, 'svc', controller_loop_gap=1.0)
    endpoint = result['endpoint']
    try:
        deadline = time.time() + 90
        ready = False
        while time.time() < deadline:
            st = serve_core.status('svc')
            if st and any(
                    r['status'] == serve_state.ReplicaStatus.READY
                    for r in st[0]['replicas']):
                ready = True
                break
            time.sleep(1)
        assert ready, serve_core.status('svc')
        resp = requests.get(endpoint + '/', timeout=10)
        assert resp.status_code == 200
    finally:
        serve_core.down('svc')
    assert serve_core.status('svc') == []


_TAG_SERVER = (
    'python -c "'
    'import http.server, os\n'
    'class H(http.server.BaseHTTPRequestHandler):\n'
    '    def do_GET(self):\n'
    "        body = os.environ.get('SKYTPU_TEST_TAG', '?').encode()\n"
    '        self.send_response(200)\n'
    "        self.send_header('Content-Length', str(len(body)))\n"
    '        self.end_headers()\n'
    '        self.wfile.write(body)\n'
    '    def log_message(self, *a):\n'
    '        pass\n'
    "http.server.HTTPServer(('127.0.0.1', "
    "int(os.environ['SKYTPU_SERVE_PORT'])), H).serve_forever()\n"
    '"')


def _tag_task(tag: str, spec: ServiceSpec) -> task_lib.Task:
    task = task_lib.Task('svc', run=_TAG_SERVER,
                         envs={'SKYTPU_TEST_TAG': tag})
    task.set_resources(resources_lib.Resources(cloud='local'))
    task.service = spec
    return task


@pytest.mark.slow
def test_serve_rolling_update(isolated_state, monkeypatch):
    """v1 serves until v2 is fully READY, then drains; the endpoint
    flips from v1 to v2 with no downtime."""
    monkeypatch.setenv('SKYTPU_SERVE_DB',
                       str(isolated_state / 'serve.db'))
    monkeypatch.setenv('SKYTPU_SERVE_LOG_DIR',
                       str(isolated_state / 'serve_logs'))
    spec = ServiceSpec(min_replicas=1, replica_port=18180,
                       initial_delay_seconds=60,
                       readiness_timeout_seconds=3)
    result = serve_core.up(_tag_task('v1', spec), 'svc',
                           controller_loop_gap=1.0)
    endpoint = result['endpoint']
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            st = serve_core.status('svc')
            if st and any(
                    r['status'] == serve_state.ReplicaStatus.READY
                    for r in st[0]['replicas']):
                break
            time.sleep(1)
        assert requests.get(endpoint, timeout=10).text == 'v1'

        update = serve_core.update(_tag_task('v2', spec), 'svc')
        assert update['version'] == 2
        deadline = time.time() + 120
        rolled = False
        while time.time() < deadline:
            st = serve_core.status('svc')[0]
            live = [r for r in st['replicas']
                    if r['status'] not in
                    (serve_state.ReplicaStatus.SHUTDOWN,)]
            # The service must never drop to zero READY replicas.
            if (live and all(r['version'] == 2 for r in live) and
                    any(r['status'] == serve_state.ReplicaStatus.READY
                        for r in live)):
                rolled = True
                break
            time.sleep(1)
        assert rolled, serve_core.status('svc')
        assert requests.get(endpoint, timeout=10).text == 'v2'
    finally:
        serve_core.down('svc')


@pytest.mark.slow
def test_serve_spot_mix(isolated_state, monkeypatch):
    """use_spot + base_ondemand_fallback_replicas=1 yields one spot
    and one on-demand replica on the hermetic local cloud."""
    monkeypatch.setenv('SKYTPU_SERVE_DB',
                       str(isolated_state / 'serve.db'))
    monkeypatch.setenv('SKYTPU_SERVE_LOG_DIR',
                       str(isolated_state / 'serve_logs'))
    spec = ServiceSpec(min_replicas=1, replica_port=18280,
                       initial_delay_seconds=60,
                       readiness_timeout_seconds=3,
                       use_spot=True,
                       base_ondemand_fallback_replicas=1)
    serve_core.up(_tag_task('spot', spec), 'svc',
                  controller_loop_gap=1.0)
    try:
        deadline = time.time() + 90
        ok = False
        while time.time() < deadline:
            st = serve_core.status('svc')
            if st:
                ready = [r for r in st[0]['replicas']
                         if r['status'] ==
                         serve_state.ReplicaStatus.READY]
                if len(ready) >= 2:
                    assert sorted(r['is_spot'] for r in ready) == [
                        False, True]
                    ok = True
                    break
            time.sleep(1)
        assert ok, serve_core.status('svc')
    finally:
        serve_core.down('svc')


# ------------------------------------------- LB resilience/streaming

def _run_async(coro):
    import asyncio
    return asyncio.run(coro)


def test_lb_retries_dead_replica_and_drains():
    """A request routed at a dead replica is transparently retried on
    a live one (connect failure = replica never saw it); drain()
    excludes a URL from picking and waits out its in-flight work."""
    import asyncio

    import aiohttp
    from aiohttp import web

    from skypilot_tpu.serve.load_balancer import LoadBalancer

    async def scenario():
        release = asyncio.Event()
        hits = []

        async def handler(request):
            hits.append(request.path)
            await release.wait()
            return web.json_response({'ok': True})

        app = web.Application()
        app.router.add_route('*', '/{tail:.*}', handler)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, '127.0.0.1', 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        live = f'http://127.0.0.1:{port}'

        # A port with nothing listening: connection refused.
        sock_site = web.TCPSite(runner, '127.0.0.1', 0)
        await sock_site.start()
        dead_port = sock_site._server.sockets[0].getsockname()[1]
        await sock_site.stop()
        dead = f'http://127.0.0.1:{dead_port}'

        lb = LoadBalancer(port=0, policy='round_robin')
        await lb.start()
        lb.set_replica_urls([dead, live])
        base = f'http://127.0.0.1:{lb.bound_port}'
        try:
            async with aiohttp.ClientSession() as session:
                # Fire enough requests that round-robin lands some on
                # the dead replica; all must succeed via retry.
                release.set()
                results = await asyncio.gather(*[
                    session.post(base + '/generate', json={'i': i})
                    for i in range(4)
                ])
                assert all(r.status == 200 for r in results)
                assert len(hits) == 4

                # Drain: in-flight request finishes first.
                release.clear()
                inflight = asyncio.create_task(
                    session.post(base + '/generate', json={}))
                while lb.inflight(live) == 0:
                    await asyncio.sleep(0.01)
                drain_task = asyncio.create_task(lb.drain(live))
                await asyncio.sleep(0.05)
                assert not drain_task.done()      # still in flight
                assert lb.policy.pick(exclude=lb._draining) is None \
                    or lb.policy.pick(exclude=lb._draining) == dead
                release.set()
                assert await drain_task is True
                resp = await inflight
                assert resp.status == 200
        finally:
            await lb.stop()
            await runner.cleanup()

    _run_async(scenario())


def test_lb_streams_chunks_incrementally():
    """Response bodies are proxied chunk-by-chunk: the client sees the
    first SSE event while the replica still holds the connection."""
    import asyncio

    import aiohttp
    from aiohttp import web

    from skypilot_tpu.serve.load_balancer import LoadBalancer

    async def scenario():
        gate = asyncio.Event()

        async def handler(request):
            resp = web.StreamResponse(
                headers={'Content-Type': 'text/event-stream'})
            await resp.prepare(request)
            await resp.write(b'data: {"tokens": [1]}\n\n')
            await gate.wait()
            await resp.write(b'data: {"done": true}\n\n')
            await resp.write_eof()
            return resp

        app = web.Application()
        app.router.add_route('*', '/{tail:.*}', handler)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, '127.0.0.1', 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        lb = LoadBalancer(port=0)
        await lb.start()
        lb.set_replica_urls([f'http://127.0.0.1:{port}'])
        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        f'http://127.0.0.1:{lb.bound_port}/generate',
                        json={}) as resp:
                    assert resp.status == 200
                    # First chunk arrives while the replica handler is
                    # still blocked on `gate` — proof of streaming
                    # passthrough (a buffering proxy would hang here).
                    first = await asyncio.wait_for(
                        resp.content.readuntil(b'\n\n'), timeout=5)
                    assert b'"tokens": [1]' in first
                    gate.set()
                    rest = await resp.content.read()
                    assert b'"done": true' in rest
        finally:
            await lb.stop()
            await runner.cleanup()

    _run_async(scenario())


# ------------------------------------------- autoscaler durability

def test_autoscaler_state_roundtrip(isolated_state, monkeypatch):
    """A restarted controller restores the QPS window + target: no
    spurious downscale after restart under load."""
    monkeypatch.setenv('SKYTPU_SERVE_DB',
                       str(isolated_state / 'serve.db'))
    spec = ServiceSpec(min_replicas=1, max_replicas=10,
                       target_qps_per_replica=1.0,
                       upscale_delay_seconds=1,
                       downscale_delay_seconds=1000)
    scaler = autoscalers.RequestRateAutoscaler(spec)
    now = time.time()
    for i in range(300):
        scaler.record_request(now - 30 + i * 0.1)   # ~5 qps
    scaler.evaluate(now=now)                         # start clocks
    scaler.evaluate(now=now + 2)                     # upscale fires
    assert scaler.evaluate(now=now + 2).target_replicas == 5
    serve_state.save_autoscaler_state('svc', scaler.to_state())

    # "Restart": fresh autoscaler restores persisted state.
    reborn = autoscalers.RequestRateAutoscaler(spec)
    reborn.restore(serve_state.load_autoscaler_state('svc'))
    decision = reborn.evaluate(now=time.time())
    assert decision.target_replicas == 5   # not reset to min=1
    assert reborn.current_qps() > 0

    # Old timestamps age out of the restored window.
    spec2 = ServiceSpec(min_replicas=1, max_replicas=3,
                        target_qps_per_replica=1.0)
    capped = autoscalers.RequestRateAutoscaler(spec2)
    capped.restore(serve_state.load_autoscaler_state('svc'))
    assert capped.evaluate(now=time.time()).target_replicas <= 3
