"""Mesh-native fast path (PR "shard_map'd paged-attention kernels +
TP-sharded prefix cache"): the shard_map wrappers around the three
Pallas kernels must be BITWISE identical to the jitted single-device
kernels (attention is embarrassingly parallel per kv head), dead
pages must stay unread under a sharded cache (NaN poison), and a
tensor-parallel ServingEngine with the prefix cache AND speculative
decoding enabled must reproduce the unsharded engine's greedy tokens
with zero post-warmup recompiles. Runs on forced-host-device CPU
meshes (conftest exports XLA_FLAGS=--xla_force_host_platform_
device_count=8); Pallas runs in interpret mode off-TPU.
"""
import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu import models
from skypilot_tpu.ops import decode_attention as da
from skypilot_tpu.parallel import make_mesh, plan_mesh

# ops/__init__ re-exports a flash_attention FUNCTION that shadows the
# module on attribute import.
fa = importlib.import_module('skypilot_tpu.ops.flash_attention')

HD = 16


def _mesh(tp, dp=1):
    plan = plan_mesh(tp * dp, tp=tp, dp=dp, fsdp=1, sp=1)
    return make_mesh(plan, devices=jax.devices()[:tp * dp])


def _decode_inputs(b, s, n_kv, rep, *, quant=False, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 8)
    q = jax.random.normal(ks[0], (b, n_kv * rep, HD), jnp.bfloat16)
    if quant:
        kc = jax.random.randint(ks[1], (b, s, n_kv, HD), -127, 128,
                                jnp.int8)
        vc = jax.random.randint(ks[2], (b, s, n_kv, HD), -127, 128,
                                jnp.int8)
        ksc = (jax.random.uniform(ks[3], (b, s, n_kv)) * 0.02 +
               0.001).astype(jnp.bfloat16)
        vsc = (jax.random.uniform(ks[4], (b, s, n_kv)) * 0.02 +
               0.001).astype(jnp.bfloat16)
    else:
        kc = jax.random.normal(ks[1], (b, s, n_kv, HD), jnp.bfloat16)
        vc = jax.random.normal(ks[2], (b, s, n_kv, HD), jnp.bfloat16)
        ksc = vsc = None
    k_self = jax.random.normal(ks[5], (b, n_kv, HD), jnp.bfloat16)
    v_self = jax.random.normal(ks[6], (b, n_kv, HD), jnp.bfloat16)
    return q, kc, vc, ksc, vsc, k_self, v_self


# ------------------------------------------------- kernel-level parity


@pytest.mark.parametrize('tp,dp', [(2, 1), (4, 1), (2, 2)],
                         ids=['tp2', 'tp4', 'tp2dp2'])
@pytest.mark.parametrize('quant', [False, True],
                         ids=['bf16', 'int8kv'])
def test_sharded_paged_decode_bitwise(tp, dp, quant):
    """shard_map'd paged decode == the jitted single-device kernel,
    bit for bit (both sides jitted: eager-vs-jit XLA fusion noise is
    not what this asserts)."""
    b, s, n_kv, rep, page = 4, 64, 4, 2, 16
    q, kc, vc, ksc, vsc, k_self, v_self = _decode_inputs(
        b, s, n_kv, rep, quant=quant)
    lengths = jnp.asarray([5, 17, 32, 64], jnp.int32)
    valid = (jnp.arange(s)[None, :] < lengths[:, None])
    mesh = _mesh(tp, dp)

    want = jax.jit(lambda *a: da.paged_gqa_decode_attention(
        *a, k_self=k_self, v_self=v_self, k_scale=ksc, v_scale=vsc,
        page=page))(q, kc, vc, valid, lengths)
    got = jax.jit(lambda *a: da.sharded_paged_gqa_decode_attention(
        *a, k_self=k_self, v_self=v_self, k_scale=ksc, v_scale=vsc,
        mesh=mesh, page=page))(q, kc, vc, valid, lengths)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sharded_decode_rejects_indivisible_heads():
    q, kc, vc, _, _, k_self, v_self = _decode_inputs(2, 32, 3, 2)
    valid = jnp.ones((2, 32), bool)
    lengths = jnp.full((2,), 32, jnp.int32)
    with pytest.raises(ValueError, match='not divisible'):
        da.sharded_paged_gqa_decode_attention(
            q, kc, vc, valid, lengths, k_self=k_self, v_self=v_self,
            mesh=_mesh(2), page=16)


def test_sharded_decode_dead_pages_never_read():
    """NaN poison beyond each row's bound under the SHARDED cache:
    the per-shard kernel's page skipping must survive shard_map (a
    gather-then-mask rewrite would surface the NaNs)."""
    b, s, n_kv, rep, page = 4, 64, 4, 2, 16
    q, kc, vc, _, _, k_self, v_self = _decode_inputs(b, s, n_kv, rep)
    lengths = jnp.asarray([5, 17, 32, 48], jnp.int32)
    valid = (jnp.arange(s)[None, :] < lengths[:, None])
    pk, pv = np.asarray(kc, np.float32), np.asarray(vc, np.float32)
    for row, ln in enumerate([5, 17, 32, 48]):
        first_dead = -(-ln // page)        # ceil: pages past the bound
        pk[row, first_dead * page:] = np.nan
        pv[row, first_dead * page:] = np.nan
    pk = jnp.asarray(pk, jnp.bfloat16)
    pv = jnp.asarray(pv, jnp.bfloat16)

    got = jax.jit(lambda *a: da.sharded_paged_gqa_decode_attention(
        *a, k_self=k_self, v_self=v_self, mesh=_mesh(2),
        page=page))(q, pk, pv, valid, lengths)
    assert np.isfinite(np.asarray(got, np.float32)).all()


def test_sharded_chunk_prefill_pallas_bitwise():
    """shard_map'd chunk-prefill Pallas kernel == jitted unsharded
    (kv heads over 'tp', rows replicated)."""
    g, c, s, n_kv, rep = 2, 16, 64, 4, 2
    ks = jax.random.split(jax.random.PRNGKey(3), 4)
    q = jax.random.normal(ks[0], (g, c, n_kv * rep, HD), jnp.bfloat16)
    k = jax.random.normal(ks[1], (g, s, n_kv, HD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (g, s, n_kv, HD), jnp.bfloat16)
    off = jnp.asarray([0, 16], jnp.int32)

    want = jax.jit(lambda *a: fa.chunk_prefill_attention(
        *a, impl='pallas', interpret=True))(q, k, v, off)
    got = jax.jit(lambda *a: fa.chunk_prefill_attention(
        *a, impl='pallas', interpret=True, mesh=_mesh(2)))(q, k, v,
                                                           off)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sharded_verify_pallas_bitwise():
    """shard_map'd verify Pallas kernel == jitted unsharded (kv heads
    on 'tp', batch on the data axes, seg_start replicated)."""
    b, vq, s, n_kv, rep = 4, 4, 64, 4, 2
    ks = jax.random.split(jax.random.PRNGKey(5), 4)
    q = jax.random.normal(ks[0], (b, vq, n_kv * rep, HD),
                          jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, s, n_kv, HD), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, s, n_kv, HD), jnp.bfloat16)
    seg = 32
    valid = (jnp.arange(s)[None, :] < seg) & jnp.ones((b, 1), bool)

    want = jax.jit(lambda *a: fa.verify_attention(
        *a, impl='pallas', interpret=True))(q, k, v, valid, seg)
    got = jax.jit(lambda *a: fa.verify_attention(
        *a, impl='pallas', interpret=True, mesh=_mesh(2, dp=2)))(
            q, k, v, valid, seg)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ------------------------------------------------ engine-level parity


def _prompt(cfg, n, seed):
    rng = np.random.default_rng(seed)
    return [int(t) for t in rng.integers(0, cfg.vocab_size, n)]


_TP_PARITY_KW = dict(batch_size=2, max_prompt=32, max_seq=128,
                     decode_chunk=4, page=16, prefill_chunk=16,
                     prefill_budget=32, decode_attn='paged',
                     prefix_cache=True, spec_decode=True, spec_k=2)


@pytest.fixture(scope='module')
def tp_parity_oracle():
    """The unsharded oracle arm for the tp parity gate, built ONCE
    for the module (test-budget satellite): the plain engine, its
    requests, and its greedy tokens are identical across the tp
    parametrizations — only the mesh arm varies — so the three runs
    share one interpret-mode Pallas oracle instead of paying the
    plain engine's compile + run three times."""
    from skypilot_tpu.models.serving_engine import (Request,
                                                    ServingEngine)
    # tp=4 needs n_kv_heads % 4 == 0.
    cfg = models.LlamaConfig.tiny(n_heads=8, n_kv_heads=4,
                                  max_seq=256)
    params = models.init_params(cfg, jax.random.PRNGKey(0))
    # Shared prefix of exactly one prefix-cache page (16 tokens):
    # request 0 publishes it at retirement, request 2 (admitted after
    # a slot frees) hits it.
    shared = _prompt(cfg, 16, 99)
    reqs = [Request(i, shared + _prompt(cfg, 4 + i, i), max_new=5)
            for i in range(3)]
    plain = ServingEngine(params, cfg, **_TP_PARITY_KW)
    assert plain.attn_impl == 'paged'
    want = plain.run([Request(r.request_id, list(r.tokens),
                              max_new=r.max_new) for r in reqs])
    return cfg, params, reqs, {i: want[i].tokens for i in want}


@pytest.mark.parametrize('tp', [1, 2, 4])
def test_tp_engine_prefix_spec_paged_parity(tp, tp_parity_oracle):
    """The acceptance gate: for tp in {1, 2, 4}, a mesh engine with
    the prefix cache AND speculative decoding enabled, dispatching
    the PAGED Pallas impl (interpret on CPU), serves bitwise the
    unsharded engine's greedy tokens — with a genuinely sharded
    cache and zero recompiles after warmup."""
    from skypilot_tpu.models.serving_engine import (Request,
                                                    ServingEngine)
    cfg, params, reqs, want = tp_parity_oracle
    kw = _TP_PARITY_KW

    eng = ServingEngine(params, cfg, mesh=_mesh(tp), **kw)
    assert eng.attn_impl == 'paged'
    assert eng.prefix is not None            # warn+disable is gone
    eng.warmup()
    # The cache (and the prefix pool) really shard on the kv-head
    # 'tp' axis — not a replicated fallback.
    k_spec = str(eng.cache['k'].sharding.spec)
    pool_spec = str(eng.prefix.pool['k'].sharding.spec)
    if tp > 1:
        assert 'tp' in k_spec and 'tp' in pool_spec
    counts = (eng._decode._cache_size(), eng._mixed._cache_size(),
              eng._spec._cache_size(),
              eng.prefix.compile_cache_sizes())
    got = eng.run([Request(r.request_id, list(r.tokens),
                           max_new=r.max_new) for r in reqs])
    assert counts == (eng._decode._cache_size(),
                      eng._mixed._cache_size(),
                      eng._spec._cache_size(),
                      eng.prefix.compile_cache_sizes())
    for i in want:
        assert got[i].tokens == want[i], (
            tp, i, got[i].tokens, want[i])
    assert eng.prefix.hits > 0               # prefix reuse really ran


def test_engine_page_misalignment_downgrade_observable():
    """The only remaining decode downgrade (max_seq not a page
    multiple) warns once and exports the effective impl to the
    skytpu_engine_attn_impl info gauge; meshes no longer downgrade."""
    from skypilot_tpu import metrics as metrics_lib
    from skypilot_tpu.models.serving_engine import ServingEngine
    cfg = models.LlamaConfig.tiny()
    params = models.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(params, cfg, batch_size=2, max_prompt=32,
                        max_seq=120, page=16, decode_attn='paged')
    assert eng.attn_impl == 'lax'
    assert metrics_lib.summary().get(
        'skytpu_engine_attn_impl{impl="lax"}') == 1.0
    ok = ServingEngine(params, cfg, batch_size=2, max_prompt=32,
                       max_seq=128, page=16, decode_attn='paged')
    assert ok.attn_impl == 'paged'
    assert metrics_lib.summary().get(
        'skytpu_engine_attn_impl{impl="paged"}') == 1.0


def test_health_reports_mesh_shape():
    """/health carries mesh shape / device count (None single-chip)
    so the harness computes per-chip normalization from the replica
    itself."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from skypilot_tpu.models.serving_engine import ServingEngine
    from skypilot_tpu.models.serving_http import EngineServer

    cfg = models.LlamaConfig.tiny()
    params = models.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(params, cfg, batch_size=2, max_prompt=32,
                        max_seq=128, decode_chunk=4, mesh=_mesh(2))
    server = EngineServer(eng)
    server._ready.set()

    async def scenario():
        async with TestClient(TestServer(server.make_app())) as c:
            r = await c.get('/health')
            return r.status, await r.json()

    status, body = asyncio.run(scenario())
    assert status == 200
    assert body['mesh'] == {'devices': 2, 'axes': {'tp': 2}, 'tp': 2}
    server.stop()

    unsharded = ServingEngine(params, cfg, batch_size=2,
                              max_prompt=32, max_seq=128,
                              decode_chunk=4)
    assert unsharded.mesh_info() is None


# ------------------------------------------ dryrun harness scoring


def test_dryrun_parent_scores_sentinel_not_exit_code(monkeypatch,
                                                     capsys):
    """MULTICHIP flake fix: a child that prints the ALL OK sentinel
    but dies rc=-6 at interpreter teardown is a SUCCESS (no
    deadline-blowing wipe-and-retry); a child without the sentinel
    still triggers exactly one cache-wipe retry before raising."""
    import subprocess

    import __graft_entry__ as ge

    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)
        return subprocess.CompletedProcess(
            cmd, returncode=-6,
            stdout='dryrun_multichip(8): ALL OK\n', stderr='')

    monkeypatch.setattr(subprocess, 'run', fake_run)
    ge.dryrun_multichip(8)                   # must not raise
    assert len(calls) == 1                   # no retry on teardown rc
    assert 'scoring on the final outcome' in capsys.readouterr().err

    calls.clear()

    def fake_fail(cmd, **kw):
        calls.append(cmd)
        return subprocess.CompletedProcess(
            cmd, returncode=1, stdout='no sentinel here\n', stderr='')

    wiped = []
    monkeypatch.setattr(subprocess, 'run', fake_fail)
    monkeypatch.setattr(
        'shutil.rmtree', lambda p, **kw: wiped.append(p))
    with pytest.raises(RuntimeError, match='compile-cache wipe'):
        ge.dryrun_multichip(8)
    assert len(calls) == 2 and len(wiped) == 1
