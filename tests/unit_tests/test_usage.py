"""Usage telemetry: schema-scrubbed local JSONL sink, remote
collector batching, API-server heartbeat, and the opt-out env
(reference sky/usage/usage_lib.py:341,467)."""
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from skypilot_tpu.usage import usage_lib


class _Collector:
    """Tiny HTTP collector recording /usage and /heartbeat posts."""

    def __init__(self):
        self.usage = []
        self.heartbeats = []
        outer = self

        class Handler(BaseHTTPRequestHandler):

            def do_POST(self):
                n = int(self.headers.get('Content-Length', 0))
                body = json.loads(self.rfile.read(n))
                if self.path == '/usage':
                    outer.usage.append(body)
                elif self.path == '/heartbeat':
                    outer.heartbeats.append(body)
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.server = HTTPServer(('127.0.0.1', 0), Handler)
        self.url = f'http://127.0.0.1:{self.server.server_port}'
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()


@pytest.fixture
def collector(monkeypatch, tmp_path):
    c = _Collector()
    monkeypatch.setenv('SKYTPU_USAGE_COLLECTOR_URL', c.url)
    monkeypatch.setenv('SKYTPU_DATA_DIR', str(tmp_path))
    monkeypatch.delenv('SKYTPU_DISABLE_USAGE', raising=False)
    usage_lib._pending.clear()
    yield c
    c.stop()
    usage_lib._pending.clear()


def test_local_sink_scrubs_fields(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYTPU_DATA_DIR', str(tmp_path))
    monkeypatch.delenv('SKYTPU_USAGE_COLLECTOR_URL', raising=False)
    usage_lib.record_event('launch', cloud='gcp', num_chips=8,
                           secret_path='/home/me/key',   # not allowed
                           status='ok')
    with open(usage_lib.messages_path(), encoding='utf-8') as f:
        event = json.loads(f.readlines()[-1])
    assert event['op'] == 'launch'
    assert event['cloud'] == 'gcp'
    assert event['num_chips'] == 8
    assert 'secret_path' not in event


def test_remote_batch_flush(collector):
    usage_lib.record_event('launch', cloud='gcp', num_chips=8)
    usage_lib.record_event('down', cloud='gcp')
    assert usage_lib.flush_remote()
    assert len(collector.usage) == 1
    batch = collector.usage[0]
    assert batch['source']
    ops = [e['op'] for e in batch['events']]
    assert ops == ['launch', 'down']
    # Whitelist holds on the wire too.
    assert all('secret' not in json.dumps(e) for e in batch['events'])
    # Nothing pending -> flush is a cheap no-op True.
    assert usage_lib.flush_remote()
    assert len(collector.usage) == 1


def test_heartbeat_posts_liveness(collector):
    assert usage_lib.heartbeat(op='api_server')
    hb = collector.heartbeats[-1]
    assert hb['source']
    assert 'n_clusters' in hb
    assert hb['op'] == 'api_server'


def test_opt_out_disables_both_sinks(collector, monkeypatch,
                                     tmp_path):
    monkeypatch.setenv('SKYTPU_DISABLE_USAGE', '1')
    usage_lib.record_event('launch', cloud='gcp')
    assert not usage_lib.heartbeat()
    assert not usage_lib.flush_remote()
    assert collector.usage == []
    assert collector.heartbeats == []


def test_server_heartbeat_ctx(collector, monkeypatch):
    """The API server beats on startup (fleet visibility for team
    deployments)."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from skypilot_tpu.server import server as server_mod

    monkeypatch.setenv('SKYTPU_HEARTBEAT_INTERVAL', '3600')

    async def run():
        app = server_mod.make_app()
        async with TestClient(TestServer(app)) as client:
            resp = await client.get('/api/health')
            assert resp.status == 200
            for _ in range(100):
                if collector.heartbeats:
                    break
                await asyncio.sleep(0.05)
    asyncio.run(run())
    assert collector.heartbeats
    assert collector.heartbeats[0]['op'] == 'api_server'
