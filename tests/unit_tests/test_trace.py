"""Distributed tracing (skypilot_tpu/trace/, docs/tracing.md):
span semantics, cross-process/HTTP context propagation, Chrome
export, metrics exemplar linkage, and the instrumented serve path."""
import asyncio
import json
import logging
import os
import subprocess
import sys
import threading
import time

import pytest

from skypilot_tpu import metrics
from skypilot_tpu import trace as trace_lib
from skypilot_tpu.trace import core as trace_core
from skypilot_tpu.trace import export

pytestmark = pytest.mark.trace

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture
def trace_dir(tmp_path, monkeypatch):
    spool = tmp_path / 'spool'
    monkeypatch.setenv(trace_core.TRACE_DIR_ENV, str(spool))
    monkeypatch.delenv(trace_core.TRACE_CONTEXT_ENV, raising=False)
    yield str(spool)


@pytest.fixture
def seeded(monkeypatch):
    trace_lib.seed_ids(0)
    trace_lib.set_clock(None)
    yield
    trace_lib.seed_ids(None)
    trace_lib.set_clock(None)


# ------------------------------------------------------------ core
def test_span_nesting_and_attrs(trace_dir):
    with trace_lib.span('outer', kind='test') as outer:
        assert outer is not None and outer.recorded
        assert trace_lib.current_span() is outer
        with trace_lib.span('inner') as inner:
            inner.set_attr(extra=7)
        with trace_lib.span('inner2'):
            pass
    assert trace_lib.current_span() is None
    spans = {s['name']: s for s in export.read_spans(trace_dir)}
    assert set(spans) == {'outer', 'inner', 'inner2'}
    assert spans['outer']['attrs'] == {'kind': 'test'}
    assert spans['inner']['attrs'] == {'extra': 7}
    for name in ('inner', 'inner2'):
        assert spans[name]['trace_id'] == spans['outer']['trace_id']
        assert spans[name]['parent_id'] == spans['outer']['span_id']
    assert spans['outer']['parent_id'] is None
    assert spans['outer']['start'] <= spans['inner']['start']
    assert spans['inner']['end'] <= spans['outer']['end']


def test_span_decorator_and_error_attr(trace_dir):

    @trace_lib.span('decorated.fn', layer='x')
    def fn():
        return 41

    assert fn() == 41
    with pytest.raises(ValueError):
        with trace_lib.span('failing.op'):
            raise ValueError('boom')
    spans = {s['name']: s for s in export.read_spans(trace_dir)}
    assert spans['decorated.fn']['attrs'] == {'layer': 'x'}
    assert 'ValueError: boom' in spans['failing.op']['attrs']['error']


def test_disabled_mode_no_file_io(monkeypatch):
    """Zero overhead off: no ids on the contextvar path, no record
    emission, no spool writes — asserted by making emission fatal."""
    monkeypatch.delenv(trace_core.TRACE_DIR_ENV, raising=False)
    monkeypatch.delenv('SKYTPU_TIMELINE_FILE_PATH', raising=False)
    monkeypatch.delenv(trace_core.TRACE_CONTEXT_ENV, raising=False)

    def boom(_):
        raise AssertionError('span emission in disabled mode')

    monkeypatch.setattr(trace_core, '_emit', boom)
    with trace_lib.span('nothing') as sp:
        assert sp is None
        assert trace_lib.current_span() is None
    manual = trace_lib.start_span('manual.timer')
    assert not manual.recorded
    assert manual.exemplar is None
    manual.finish()  # must not emit
    assert manual.duration >= 0.0
    assert trace_lib.current_trace_id() is None
    assert trace_lib.traceparent_headers() == {}
    assert trace_lib.child_env() == {}


def test_thread_isolation(trace_dir):
    """Worker threads start clean: no inherited contextvar parent,
    fresh trace ids."""
    got = {}
    with trace_lib.span('main.op') as main_span:

        def worker():
            assert trace_lib.current_span() is None
            sp = trace_lib.start_span('worker.op')
            got['trace'] = sp.trace_id
            got['parent'] = sp.parent_id
            sp.finish()

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        t.join()
        assert got['trace'] != main_span.trace_id
        assert got['parent'] is None


def test_traceparent_round_trip(seeded):
    ctx = trace_core.SpanContext('ab' * 16, 'cd' * 8)
    assert trace_lib.parse_traceparent(
        trace_lib.format_traceparent(ctx)) == ctx
    for bad in (None, '', 'nonsense', '00-xyz-123-01',
                '00-' + 'ab' * 16 + '-short-01'):
        assert trace_lib.parse_traceparent(bad) is None
    # Case-insensitive header lookup.
    hdr = {'TraceParent': trace_lib.format_traceparent(ctx)}
    assert trace_lib.context_from_headers(hdr) == ctx


def test_subprocess_propagation_round_trip(trace_dir):
    """SKYTPU_TRACE_CONTEXT: a child process's span parents under
    the launching process's active span — one trace id across the
    process boundary (the jobs-controller / bench-child shape)."""
    code = ('from skypilot_tpu import trace\n'
            "with trace.span('child.work'):\n"
            '    pass\n')
    with trace_lib.span('parent.op') as parent:
        env = dict(os.environ)
        block = trace_lib.child_env(env)
        assert trace_core.TRACE_CONTEXT_ENV in block
        env['PYTHONPATH'] = (_REPO_ROOT + os.pathsep +
                             env.get('PYTHONPATH', ''))
        subprocess.run([sys.executable, '-c', code], env=env,
                       check=True, timeout=120)
    spans = {s['name']: s for s in export.read_spans(trace_dir)}
    child, par = spans['child.work'], spans['parent.op']
    assert child['trace_id'] == par['trace_id']
    assert child['parent_id'] == par['span_id']
    assert child['pid'] != par['pid']


def test_slow_span_logged(trace_dir, monkeypatch):
    monkeypatch.setenv(trace_core.SLOW_SPAN_ENV, '0.001')
    records = []

    class Capture(logging.Handler):

        def emit(self, record):
            records.append(record.getMessage())

    handler = Capture()
    logging.getLogger('skypilot_tpu').addHandler(handler)
    try:
        with trace_lib.span('slowpoke') as sp:
            time.sleep(0.01)
    finally:
        logging.getLogger('skypilot_tpu').removeHandler(handler)
    hits = [m for m in records if 'slow span' in m]
    assert hits and 'slowpoke' in hits[0] and sp.trace_id in hits[0]


# ---------------------------------------------------------- export
def test_chrome_export_golden(trace_dir, seeded):
    """Deterministic ids + clock -> byte-stable Chrome trace (the
    format contract tools load; pid/tid are process-real)."""
    now = [1000.0]

    def clock():
        now[0] += 1.0
        return now[0]

    trace_lib.set_clock(clock)
    with trace_lib.span('launch', cloud='local'):
        with trace_lib.span('provision.local.run_instances'):
            pass
    trace_lib.set_clock(None)
    got = export.to_chrome(export.read_spans(trace_dir))
    pid, tid = os.getpid(), threading.get_ident()
    want = {
        'traceEvents': [
            {
                'name': 'launch',
                'cat': 'skypilot_tpu',
                'ph': 'X',
                'ts': 1001000000.0,
                'dur': 3000000.0,
                'pid': pid,
                'tid': tid,
                'args': {
                    'cloud': 'local',
                    'trace_id': 'e3e70682c2094cac629f6fbed82c07cd',
                    'span_id': '0a5d2f346baa9455',
                },
            },
            {
                'name': 'provision.local.run_instances',
                'cat': 'skypilot_tpu',
                'ph': 'X',
                'ts': 1002000000.0,
                'dur': 1000000.0,
                'pid': pid,
                'tid': tid,
                'args': {
                    'trace_id': 'e3e70682c2094cac629f6fbed82c07cd',
                    'span_id': 'f728b4fa42485e3a',
                    'parent_id': '0a5d2f346baa9455',
                },
            },
        ],
        'displayTimeUnit': 'ms',
    }
    assert got == want
    # And the payload is valid Chrome-trace JSON end to end.
    assert json.loads(json.dumps(got))['traceEvents'][0]['ph'] == 'X'


def test_cli_chrome_and_tree(trace_dir):
    with trace_lib.span('cli.root'):
        with trace_lib.span('cli.child', n=1):
            pass
    env = dict(os.environ)
    env['PYTHONPATH'] = (_REPO_ROOT + os.pathsep +
                         env.get('PYTHONPATH', ''))
    out = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.trace', '--dir',
         trace_dir, '--format', 'chrome'],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    payload = json.loads(out.stdout)
    names = [e['name'] for e in payload['traceEvents']]
    assert names == ['cli.root', 'cli.child']
    assert all(e['ph'] == 'X' for e in payload['traceEvents'])

    tree = subprocess.run(
        [sys.executable, '-m', 'skypilot_tpu.trace', '--dir',
         trace_dir, '--format', 'tree'],
        env=env, capture_output=True, text=True, timeout=120)
    assert tree.returncode == 0, tree.stderr
    assert 'cli.root' in tree.stdout
    # The child renders deeper than its parent.
    root_line = next(l for l in tree.stdout.splitlines()
                     if 'cli.root' in l)
    child_line = next(l for l in tree.stdout.splitlines()
                      if 'cli.child' in l)
    indent = lambda s: len(s) - len(s.lstrip())  # noqa: E731
    assert indent(child_line) > indent(root_line)
    assert 'n=1' in child_line


def test_export_skips_corrupt_lines(trace_dir):
    with trace_lib.span('good'):
        pass
    path = trace_lib.spool_path(trace_dir)
    with open(path, 'a', encoding='utf-8') as f:
        f.write('{"torn": \n')
        f.write('not json at all\n')
    spans = export.read_spans(trace_dir)
    assert [s['name'] for s in spans] == ['good']


# ------------------------------------------------------- exemplars
def test_histogram_exemplar_linkage():
    reg = metrics.Registry()
    h = reg.histogram('skytpu_test_linked_seconds', 'test hist',
                      buckets=(1.0,))
    h.observe(0.5, exemplar='ab' * 16)
    h.observe(0.7)  # exemplar-less observation keeps the last one
    series = reg.families()['skytpu_test_linked_seconds']['series'][0]
    assert series['exemplar'] == {'trace_id': 'ab' * 16, 'value': 0.5}
    # 0.0.4 text exposition ignores exemplars (format predates them).
    text = metrics.render(reg.families())
    assert 'exemplar' not in text and 'ab' * 16 not in text
    # Snapshot-merge carries the exemplar through (JSON round trip =
    # the spool protocol's transport).
    base = reg.families()
    other = json.loads(json.dumps(reg.families()))
    other['skytpu_test_linked_seconds']['series'][0]['exemplar'] = {
        'trace_id': 'cd' * 16, 'value': 0.9}
    metrics.merge_families(base, other)
    merged = base['skytpu_test_linked_seconds']['series'][0]
    assert merged['exemplar']['trace_id'] == 'cd' * 16
    assert merged['count'] == 4


# ------------------------------------------------- serve-path wiring
def test_lb_propagates_trace_headers(trace_dir):
    """LB -> replica: the proxied request carries a traceparent
    continuing the CLIENT's trace re-parented under the lb.proxy
    span, and a client X-Request-ID passes through untouched."""
    import aiohttp
    from aiohttp import web

    from skypilot_tpu.serve.load_balancer import LoadBalancer

    client_trace = 'ab' * 16
    client_tp = f'00-{client_trace}-{"cd" * 8}-01'
    seen = {}

    async def scenario():

        async def handler(request):
            seen['traceparent'] = request.headers.get('traceparent')
            seen['request_id'] = request.headers.get('X-Request-ID')
            return web.json_response({'ok': True})

        app = web.Application()
        app.router.add_post('/generate', handler)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, '127.0.0.1', 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]  # pylint: disable=protected-access
        lb = LoadBalancer(port=0)
        await lb.start()
        lb.set_replica_urls([f'http://127.0.0.1:{port}'])
        async with aiohttp.ClientSession() as session:
            async with session.post(
                    f'http://127.0.0.1:{lb.bound_port}/generate',
                    json={'tokens': [1]},
                    headers={'traceparent': client_tp,
                             'X-Request-ID': 'req-42'}) as resp:
                status = resp.status
                await resp.read()
        await lb.stop()
        await runner.cleanup()
        return status

    assert asyncio.run(scenario()) == 200
    got = trace_lib.parse_traceparent(seen['traceparent'])
    assert got is not None
    assert got.trace_id == client_trace        # trace continues
    assert got.span_id != 'cd' * 8             # re-parented at the LB
    assert seen['request_id'] == 'req-42'
    spans = export.read_spans(trace_dir)
    mine = {s['name']: s for s in spans
            if s['trace_id'] == client_trace}
    assert {'lb.request', 'lb.proxy'} <= set(mine)
    assert mine['lb.request']['parent_id'] == 'cd' * 8
    assert mine['lb.proxy']['parent_id'] == \
        mine['lb.request']['span_id']
    # The replica saw exactly the lb.proxy span as its parent.
    assert got.span_id == mine['lb.proxy']['span_id']
    # Span duration fed the latency histogram, trace id as exemplar.
    fam = metrics.REGISTRY.families()[
        'skytpu_lb_replica_request_seconds']
    assert fam['series'][0]['exemplar']['trace_id'] == client_trace


def test_serving_http_request_id_and_429(trace_dir):
    """X-Request-ID: echoed when given (including on 429 rejects),
    generated when absent; the http.generate span continues the
    caller's trace and records the request id."""
    import jax
    from aiohttp.test_utils import TestClient, TestServer

    from skypilot_tpu import models
    from skypilot_tpu.models.serving_engine import Request as EngReq
    from skypilot_tpu.models.serving_engine import ServingEngine
    from skypilot_tpu.models.serving_http import EngineServer

    cfg = models.LlamaConfig.tiny()
    params = models.init_params(cfg, jax.random.PRNGKey(0))
    engine = ServingEngine(params, cfg, batch_size=2, max_prompt=32,
                           max_seq=128, decode_chunk=4)
    server = EngineServer(engine, max_pending=2)
    engine.submit(EngReq('a', [1, 2, 3], 4))
    engine.submit(EngReq('b', [1, 2, 3], 4))
    client_trace = 'ef' * 16
    client_tp = f'00-{client_trace}-{"12" * 8}-01'

    async def scenario():
        async with TestClient(TestServer(server.make_app())) as client:
            full = await client.post(
                '/generate', json={'tokens': [1, 2, 3], 'max_new': 4},
                headers={'X-Request-ID': 'my-req',
                         'traceparent': client_tp})
            body = await full.json()
            bad = await client.post('/generate', json={'tokens': []})
            return (full.status, full.headers.get('X-Request-ID'),
                    body, bad.status, bad.headers.get('X-Request-ID'))

    status, echoed, body, bad_status, minted = asyncio.run(scenario())
    server.stop()
    assert status == 429 and echoed == 'my-req'
    assert body['request_id'] == 'my-req'
    assert bad_status == 400
    assert minted  # absent header -> generated id, still echoed
    assert minted != 'my-req'
    spans = [s for s in export.read_spans(trace_dir)
             if s['name'] == 'http.generate']
    mine = [s for s in spans if s['trace_id'] == client_trace]
    assert mine and mine[0]['attrs']['request_id'] == 'my-req'
    assert mine[0]['parent_id'] == '12' * 8


def test_engine_ttft_span_breakdown(trace_dir):
    """One engine request yields a span tree decomposing TTFT:
    engine.request -> queue_wait / prefill / decode.first_chunk, all
    one trace id, contiguous in time; the TTFT histogram carries the
    trace id as exemplar (single timing source)."""
    import jax

    from skypilot_tpu import models
    from skypilot_tpu.models.serving_engine import Request, ServingEngine

    cfg = models.LlamaConfig.tiny()
    params = models.init_params(cfg, jax.random.PRNGKey(0))
    engine = ServingEngine(params, cfg, batch_size=2, max_prompt=32,
                           max_seq=128, decode_chunk=4)
    results = engine.run([Request('r1', [5, 3, 2, 7], max_new=4)])
    assert len(results['r1'].tokens) == 4
    spans = export.read_spans(trace_dir)
    by_name = {}
    for s in spans:
        by_name.setdefault(s['name'], []).append(s)
    req = by_name['engine.request'][0]
    assert req['attrs'] == {'request_id': 'r1', 'prompt_len': 4,
                            'max_new': 4, 'tokens': 4}
    children = {}
    for name in ('engine.queue_wait', 'engine.prefill',
                 'engine.decode.first_chunk'):
        child = by_name[name][0]
        assert child['trace_id'] == req['trace_id']
        assert child['parent_id'] == req['span_id']
        children[name] = child
    # The decomposition is contiguous: queue-wait ends where prefill
    # begins; first-chunk decode starts when the prefill dispatch
    # returns; everything nests inside the request span.
    assert (req['start'] <= children['engine.queue_wait']['start'])
    assert (children['engine.queue_wait']['end'] <=
            children['engine.prefill']['start'] + 1e-6)
    assert (children['engine.prefill']['end'] <=
            children['engine.decode.first_chunk']['start'] + 1e-6)
    assert children['engine.decode.first_chunk']['end'] <= req['end']
    fam = metrics.REGISTRY.families()['skytpu_engine_ttft_seconds']
    assert fam['series'][0]['exemplar']['trace_id'] == req['trace_id']
    # Engine span state fully drained (no leak across requests).
    assert not engine._req_spans  # pylint: disable=protected-access


@pytest.mark.slow
def test_full_stack_single_trace(trace_dir):
    """Acceptance shape: client -> LB -> replica HTTP -> engine is
    ONE trace id whose tree is lb.request -> lb.proxy ->
    http.generate -> engine.request -> {queue_wait, prefill,
    first_chunk}."""
    import aiohttp
    import jax

    from skypilot_tpu import models
    from skypilot_tpu.models.serving_engine import ServingEngine
    from skypilot_tpu.models.serving_http import EngineServer
    from skypilot_tpu.serve.load_balancer import LoadBalancer

    cfg = models.LlamaConfig.tiny()
    params = models.init_params(cfg, jax.random.PRNGKey(0))
    engine = ServingEngine(params, cfg, batch_size=2, max_prompt=32,
                           max_seq=128, decode_chunk=4)
    server = EngineServer(engine)

    async def scenario():
        runner = await server.start(0)
        port = runner.addresses[0][1]
        lb = LoadBalancer(port=0)
        await lb.start()
        lb.set_replica_urls([f'http://127.0.0.1:{port}'])
        base = f'http://127.0.0.1:{lb.bound_port}'
        async with aiohttp.ClientSession() as session:
            for _ in range(600):
                try:
                    async with session.get(base + '/health') as r:
                        if r.status == 200:
                            break
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.1)
            else:
                raise TimeoutError('engine never became ready')
            async with session.post(
                    base + '/generate',
                    json={'tokens': [5, 3, 2], 'max_new': 3}) as r:
                assert r.status == 200
                rid = r.headers.get('X-Request-ID')
                await r.json()
        await lb.stop()
        await runner.cleanup()
        return rid

    rid = asyncio.run(scenario())
    server.stop()
    assert rid
    spans = export.read_spans(trace_dir)
    # Health probes proxy through the LB too — pick the /generate one.
    lb_req = [s for s in spans if s['name'] == 'lb.request' and
              s['attrs'].get('path') == '/generate'][0]
    tid = lb_req['trace_id']
    tree = {s['name']: s for s in spans if s['trace_id'] == tid}
    assert {'lb.request', 'lb.proxy', 'http.generate',
            'engine.request', 'engine.queue_wait', 'engine.prefill',
            'engine.decode.first_chunk'} <= set(tree)
    assert tree['lb.proxy']['parent_id'] == \
        tree['lb.request']['span_id']
    assert tree['http.generate']['parent_id'] == \
        tree['lb.proxy']['span_id']
    assert tree['engine.request']['parent_id'] == \
        tree['http.generate']['span_id']
    assert tree['http.generate']['attrs']['request_id'] == rid
