"""Task YAML parsing and Dag wiring."""
import textwrap

import pytest

from skypilot_tpu import Dag
from skypilot_tpu import Task
from skypilot_tpu import exceptions


def test_task_from_yaml(tmp_path):
    yaml_path = tmp_path / 'task.yaml'
    yaml_path.write_text(
        textwrap.dedent("""\
            name: train
            resources:
              accelerators: tpu-v5e-16
              use_spot: true
            num_nodes: 1
            setup: pip list
            run: |
              python train.py
            envs:
              MODEL: llama3
            """))
    task = Task.from_yaml(str(yaml_path))
    assert task.name == 'train'
    assert task.num_nodes == 1
    r = next(iter(task.resources))
    assert r.tpu.name == 'tpu-v5e-16'
    assert r.use_spot
    assert task.envs['MODEL'] == 'llama3'
    # Round trip.
    task2 = Task.from_yaml_config(task.to_yaml_config())
    assert task2.to_yaml_config() == task.to_yaml_config()


def test_task_yaml_unknown_field(tmp_path):
    with pytest.raises(exceptions.InvalidTaskError):
        Task.from_yaml_config({'nonexistent_field': 1})


def test_null_env_requires_value():
    with pytest.raises(exceptions.InvalidTaskError):
        Task.from_yaml_config({'run': 'echo hi', 'envs': {'TOKEN': None}})
    # Providing it via overrides works.
    t = Task.from_yaml_config({'run': 'echo hi', 'envs': {'TOKEN': None}},
                              env_overrides={'TOKEN': 'abc'})
    assert t.envs['TOKEN'] == 'abc'


def test_subschema_validation():
    """service/storage/file_mounts sub-schemas reject malformed specs
    with a jsonschema path, not a deep parser traceback."""
    base = {'run': 'echo hi'}
    bad = [
        {'service': {'replica_port': 99999}},           # > 65535
        {'service': {'load_balancing_policy': 'nope'}},
        {'service': {'replica_policy': {'min_replicas': -1}}},
        {'service': {'replica_policy': {'bogus_knob': 1}}},
        {'storage_mounts': {'/data': {'store': 'ftp'}}},
        {'storage_mounts': {'/data': {'mode': 'SYMLINK'}}},
        {'storage_mounts': {'/data': {'unknown_key': 'x'}}},
        {'file_mounts': {'/dst': {'not': 'a string'}}},
    ]
    for extra in bad:
        with pytest.raises(exceptions.InvalidTaskError):
            Task.from_yaml_config({**base, **extra})
    # The well-formed variants all pass.
    Task.from_yaml_config({
        **base,
        'service': {'replicas': 2, 'replica_port': 8080,
                    'load_balancing_policy': 'least_load'},
        'storage_mounts': {'/data': {'name': 'b', 'store': 'gcs',
                                     'mode': 'MOUNT'}},
        'file_mounts': {'/dst': 'gs://bucket/path'},
    })


def test_dag_context_and_chain():
    with Dag() as dag:
        a = Task('a', run='echo a')
        b = Task('b', run='echo b')
        a >> b
    assert len(dag) == 2
    assert dag.is_chain()
    assert dag.get_sorted_tasks() == [a, b]


def test_dag_cycle_rejected():
    with Dag() as dag:
        a = Task('a', run='true')
        b = Task('b', run='true')
        dag.add_edge(a, b)
        with pytest.raises(ValueError):
            dag.add_edge(b, a)


def test_invalid_num_nodes():
    with pytest.raises(exceptions.InvalidTaskError):
        Task(num_nodes=0)


def test_callable_run():
    t = Task(run=lambda rank, ips: f'echo rank {rank}')
    assert callable(t.run)


def test_estimate_runtime_yaml_roundtrip():
    config = {'name': 'est', 'run': 'true',
              'resources': {'cloud': 'local'},
              'estimate_runtime': 7200}
    task = Task.from_yaml_config(config)
    assert task.estimate_runtime == 7200.0
    assert task.to_yaml_config()['estimate_runtime'] == 7200.0
